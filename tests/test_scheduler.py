"""Deadline-aware continuous microbatching scheduler tests (ISSUE 7).

Covers: the admit-by-deadline policy's boundary cases, the service
model, deterministic seeded arrival processes, verdict bit-identity vs
the CPU oracle through the scheduled path (including the mesh spillover
branch on 8 virtual devices and the single-chip oversized-admission
split), the batch=32 ladder-prewarm recompile lint (the BENCH_r05
small-batch anomaly regression), deadline-miss events on the obs ring,
scheduler observability on /metrics, and the daemon ingest tick in
scheduler mode (burst larger than max_tick_packets spanning ticks)."""
import json
import os
import urllib.request

import numpy as np
import pytest

from infw import oracle, testing
from infw.scheduler import (
    ContinuousScheduler,
    DeadlinePolicy,
    FixedChunkPolicy,
    MIN_LADDER_BATCH,
    SchedulerStats,
    ServiceModel,
    WireStatsCounters,
    batch_ladder,
    data_parallel_width,
    ladder_bucket,
    prewarm_ladder,
)


# --- pure-policy units ------------------------------------------------------


def test_batch_ladder_shapes():
    assert batch_ladder(4096) == (32, 64, 128, 256, 512, 1024, 2048, 4096)
    assert batch_ladder(100) == (32, 64, 100)  # cap is always the last step
    assert batch_ladder(32) == (32,)
    assert batch_ladder(1) == (32,)  # never below the minimum bucket
    assert batch_ladder(4096)[0] == MIN_LADDER_BATCH


def test_ladder_bucket():
    assert ladder_bucket(1, 4096) == 32
    assert ladder_bucket(32, 4096) == 32
    assert ladder_bucket(33, 4096) == 64
    assert ladder_bucket(5000, 4096) == 4096  # capped
    assert ladder_bucket(100, 64) == 64


def test_service_model_ewma_and_fallbacks():
    sm = ServiceModel(default_base_s=1e-3, default_per_packet_s=1e-6)
    # cold model: linear seed
    assert sm.estimate(1024) == pytest.approx(1e-3 + 1024e-6)
    sm.observe(64, 0.004)
    assert sm.estimate(64) == pytest.approx(0.004)
    # unobserved bucket falls back to the nearest observed one
    assert sm.estimate(32) == pytest.approx(0.004)
    assert sm.estimate(4096) == pytest.approx(0.004)
    # EWMA moves toward new observations, ignores non-positive ones
    sm.observe(64, 0.008)
    assert 0.004 < sm.estimate(64) < 0.008
    sm.observe(64, -1.0)
    assert sm.estimate(64) > 0


def test_deadline_policy_admit_boundaries():
    sm = ServiceModel()
    sm.observe(32, 0.001)
    sm.observe(1024, 0.004)
    p = DeadlinePolicy(0.02, 1024, service=sm, margin_frac=0.1)
    # empty queue: nothing to do, no re-decision point
    assert p.admit(0.0, 0, 0.0, 0) == (0, None)
    # overload: a full admission regardless of pipeline state
    assert p.admit(0.0, 5000, 0.0, 99) == (1024, 0.0)
    assert p.admit(0.0, 1024, 0.0, 0) == (1024, 0.0)
    # work-conserving: pipeline has a free slot -> ship what's queued
    assert p.admit(0.0, 3, 0.0, 0) == (3, 0.0)
    assert p.admit(0.0, 3, 0.0, 1) == (3, 0.0)  # busy_depth default 2
    # pipeline busy + slack: wait for the batch to grow
    n_admit, wait = p.admit(0.0, 100, 0.0, 2)
    assert n_admit == 0 and 0 < wait < 0.02
    # slack exhausted (oldest waited too long): flush the queue as-is
    assert p.admit(1.0, 100, 1.0 - 0.019, 2) == (100, 0.0)
    # end of stream flushes regardless of slack
    assert p.admit(0.0, 100, 0.0, 2, eof=True) == (100, 0.0)


def test_deadline_policy_service_cap():
    sm = ServiceModel()
    for b in batch_ladder(4096):
        sm.observe(b, b * 20e-6)  # 20us/packet -> 1000 fit in 20ms
    p = DeadlinePolicy(0.02, 4096, service=sm, margin_frac=0.0)
    assert p.service_cap() == 512  # largest ladder step under 20ms
    # a deadline tighter than the smallest dispatch never starves below
    # the minimum ladder step
    tight = DeadlinePolicy(1e-9, 4096, service=sm)
    assert tight.service_cap() == MIN_LADDER_BATCH
    with pytest.raises(ValueError):
        DeadlinePolicy(0.0, 1024)
    with pytest.raises(ValueError):
        DeadlinePolicy(0.02, 0)


def test_fixed_chunk_policy_baseline_semantics():
    p = FixedChunkPolicy(100)
    assert p.admit(0.0, 99, 0.0, 0) == (0, None)   # waits for a full chunk
    assert p.admit(0.0, 100, 0.0, 5) == (100, 0.0)
    assert p.admit(0.0, 250, 0.0, 5) == (100, 0.0)
    assert p.admit(0.0, 7, 0.0, 0, eof=True) == (7, 0.0)  # end-of-stream flush


def test_scheduler_stats_counters():
    st = SchedulerStats()
    st.set_queue_depth(17)
    st.note_admit(40, 64)
    st.note_admit(500, 512, spilled=True)
    st.note_complete(540, 3)
    vals = st.counter_values()
    assert vals["scheduler_admitted_packets_total"] == 540
    assert vals["scheduler_batches_total"] == 2
    assert vals["scheduler_deadline_miss_total"] == 3
    assert vals["scheduler_spilled_batches_total"] == 1
    assert vals["scheduler_queue_depth"] == 17
    assert vals["scheduler_batch_size_64_total"] == 1
    assert vals["scheduler_batch_size_512_total"] == 1


def test_arrival_processes_deterministic_and_rates():
    a1 = testing.poisson_arrivals(np.random.default_rng(7), 1000.0, 5000)
    a2 = testing.poisson_arrivals(np.random.default_rng(7), 1000.0, 5000)
    assert (a1 == a2).all() and len(a1) == 5000
    assert (np.diff(a1) >= 0).all()
    # mean rate within 10% of offered at n=5000
    assert a1[-1] == pytest.approx(5.0, rel=0.1)
    b1 = testing.burst_arrivals(np.random.default_rng(7), 1000.0, 5000,
                                burst=50)
    b2 = testing.burst_arrivals(np.random.default_rng(7), 1000.0, 5000,
                                burst=50)
    assert (b1 == b2).all() and len(b1) == 5000
    # back-to-back within a burst, same mean rate overall
    assert (b1[:50] == b1[0]).all() and b1[50] > b1[0]
    assert b1[-1] == pytest.approx(5.0, rel=0.25)
    with pytest.raises(ValueError):
        testing.poisson_arrivals(np.random.default_rng(0), 0.0, 10)


# --- scheduled serving path vs the CPU oracle -------------------------------


@pytest.fixture(scope="module")
def dense_serving():
    """One dense-path classifier + pre-warmed 32..128 ladder, shared by
    the serve tests (the prewarm is the expensive part)."""
    from infw.backend.tpu import TpuClassifier

    rng = np.random.default_rng(3)
    tables = testing.random_tables_fast(
        rng, n_entries=48, width=4, v6_fraction=0.3
    )
    clf = TpuClassifier()
    clf.load_tables(tables)
    service = ServiceModel()
    prewarm_ladder(clf, batch_ladder(128), include_depth_classes=False,
                   service=service)
    return tables, clf, service


def test_scheduled_serve_bit_identical_to_oracle(dense_serving):
    tables, clf, service = dense_serving
    rng = np.random.default_rng(21)
    n = 600
    batch = testing.random_batch_fast(rng, tables, n_packets=n)
    offs = testing.poisson_arrivals(rng, 50_000.0, n)
    policy = DeadlinePolicy(0.2, 128, service=service)
    res = ContinuousScheduler(clf, policy).serve(batch, offs)
    ref = oracle.classify(tables, batch)
    assert (res.results == ref.results).all()
    assert (res.xdp == ref.xdp).all()
    st = res.stats.snapshot()
    assert st["admitted"] == n and st["completed"] == n
    assert st["queue_depth"] == 0
    assert res.batch_sizes.sum() == n
    # every latency is positive and measured from the SCHEDULED arrival
    assert (res.latency_s > 0).all()


def test_scheduled_serve_single_chip_split(dense_serving):
    """Without a spill target, an admission larger than the per-chip
    budget splits into per-budget jobs — never refused, never oversized."""
    tables, clf, service = dense_serving
    rng = np.random.default_rng(22)
    n = 500
    batch = testing.random_batch_fast(rng, tables, n_packets=n)
    policy = DeadlinePolicy(0.2, 256, service=service)
    sched = ContinuousScheduler(clf, policy, chip_budget=64)
    res = sched.serve(batch, np.zeros(n))  # one burst: queue >> budget
    assert (res.batch_sizes <= 64).all()
    assert res.batch_sizes.sum() == n
    ref = oracle.classify(tables, batch).results
    assert (res.results == ref).all()
    assert res.stats.snapshot()["spilled_batches"] == 0


def test_scheduled_serve_mesh_spillover(dense_serving):
    """The overflow path: a coalesced batch beyond the per-chip budget
    dispatches through MeshTpuClassifier across the "data" axis (8
    virtual devices), bit-identical to the oracle."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device pool")
    from infw.backend.mesh import MeshTpuClassifier

    tables, clf, service = dense_serving
    mesh_clf = MeshTpuClassifier()
    mesh_clf.load_tables(tables)
    assert data_parallel_width(mesh_clf) == len(jax.devices())
    rng = np.random.default_rng(23)
    n = 512
    batch = testing.random_batch_fast(rng, tables, n_packets=n)
    policy = DeadlinePolicy(0.5, 256, service=service)
    sched = ContinuousScheduler(
        clf, policy, chip_budget=64, spill_clf=mesh_clf
    )
    res = sched.serve(batch, np.zeros(n))  # burst -> admissions > budget
    st = res.stats.snapshot()
    assert st["spilled_batches"] > 0
    ref = oracle.classify(tables, batch).results
    assert (res.results == ref).all()


def test_prewarm_ladder_recompile_lint_batch32(dense_serving):
    """ISSUE-7 satellite: after the ladder pre-warm, serving at
    batch=32 (and every other ladder shape, both wire families) must be
    compile-free — the jitted dense wire dispatch's _cache_size must
    not grow (the BENCH_r05 11.77ms small-batch anomaly was exactly a
    first-dispatch jit specialization landing in the timed path)."""
    from infw.constants import KIND_IPV6
    from infw.kernels import pallas_dense

    tables, clf, service = dense_serving
    fn = pallas_dense.jitted_classify_pallas_wire_fused(
        clf._interpret, clf._active[2]
    )
    size0 = fn._cache_size()
    assert size0 > 0  # the prewarm populated it
    rng = np.random.default_rng(31)
    batch = testing.random_batch_fast(rng, tables, n_packets=256)
    kinds = np.asarray(batch.kind)
    for bs in (32, 64, 128):
        for fam in (kinds != KIND_IPV6, kinds == KIND_IPV6):
            idx = np.nonzero(fam)[0][:bs].astype(np.int64)
            if len(idx) == 0:
                continue
            wire, v4o = batch.pack_wire_subset(idx)
            pad = ladder_bucket(len(idx), 128) - wire.shape[0]
            if pad > 0:
                rows = np.zeros((pad, wire.shape[1]), np.uint32)
                rows[:, 0] = 3  # KIND_OTHER
                wire = np.concatenate([wire, rows])
            clf.classify_prepared(
                clf.prepare_packed(wire, v4o), apply_stats=False
            ).result()
    grew = fn._cache_size() - size0
    assert grew == 0, (
        f"{grew} jit recompiles during post-prewarm serving — the "
        "ladder prewarm does not cover every shape the scheduler emits"
    )


def test_deadline_miss_events_on_ring(dense_serving):
    """Misses are counted AND emitted as DeadlineMissRecords the events
    logger renders as lines."""
    from infw.obs.events import DeadlineMissRecord, EventRing, EventsLogger

    tables, clf, service = dense_serving
    rng = np.random.default_rng(24)
    n = 200
    batch = testing.random_batch_fast(rng, tables, n_packets=n)
    ring = EventRing(capacity=1024)
    policy = DeadlinePolicy(1e-7, 128, service=service)  # everything misses
    res = ContinuousScheduler(clf, policy, ring=ring).serve(
        batch, np.zeros(n)
    )
    st = res.stats.snapshot()
    assert st["misses"] == n
    recs = ring.pop_all()
    assert recs and all(isinstance(r, DeadlineMissRecord) for r in recs)
    assert sum(r.n_miss for r in recs) == n
    lines = []
    ring2 = EventRing(capacity=16)
    for r in recs[:2]:
        ring2.push(r)
    logger = EventsLogger(ring2, lines.append)
    logger.drain_once()
    assert lines and "scheduler deadline-miss" in lines[0]


def test_wire_stats_counters_provider(dense_serving):
    tables, clf, service = dense_serving
    prov = WireStatsCounters(lambda: clf)
    vals = prov.counter_values()
    assert vals  # the prewarm shipped wire bytes already
    assert any(k.startswith("wire_") and k.endswith("_packets_total")
               for k in vals)
    assert all(v >= 0 for v in vals.values())
    # classifiers without wire_stats (CPU reference / no classifier yet)
    assert WireStatsCounters(lambda: None).counter_values() == {}


# --- daemon integration ------------------------------------------------------


NS = "ingress-node-firewall-system"
NODE = "tpu-worker-0"


def _node_state_doc():
    from test_syncer import ingress, tcp_rule
    from infw.spec import (
        ACTION_DENY,
        IngressNodeFirewallNodeState,
        IngressNodeFirewallNodeStateSpec,
        ObjectMeta,
    )

    return IngressNodeFirewallNodeState(
        metadata=ObjectMeta(name=NODE, namespace=NS),
        spec=IngressNodeFirewallNodeStateSpec(
            interface_ingress_rules={
                "dummy0": [ingress(["10.0.0.0/8"],
                                   [tcp_rule(1, 80, ACTION_DENY)])]
            }
        ),
    ).to_dict()


def _mk_daemon(tmp_path, **kw):
    from infw.daemon import Daemon
    from infw.interfaces import Interface, InterfaceRegistry

    reg = InterfaceRegistry()
    reg.add(Interface(name="dummy0", index=10))
    base = dict(
        state_dir=str(tmp_path / "state"), node_name=NODE, namespace=NS,
        backend="tpu", poll_period_s=0.05, registry=reg, metrics_port=0,
        health_port=0, file_poll_interval_s=60.0,  # manual ticks
    )
    base.update(kw)
    return Daemon(**base)


def test_daemon_scheduler_ingest_tick(tmp_path):
    """The daemon's ingest tick in scheduler mode: deadline-sized jobs,
    correct verdicts, scheduler counters + wire bytes on /metrics, and
    the ladder pre-warm keeping the serving tick compile-free."""
    from infw.daemon import write_frames_file
    from infw.obs.pcap import build_frame
    from infw.constants import IPPROTO_TCP

    d = _mk_daemon(tmp_path, deadline_us=200_000.0, max_batch=64)
    d.start()
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(_node_state_doc(), f)
        d.scan_nodestates_once()
        clf = d.syncer.classifier
        assert clf is not None and clf.tables is not None

        mk = lambda dport: build_frame(
            "10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, dport
        )
        v6 = build_frame("2001:db8::1", "2001:db8::2", IPPROTO_TCP, 999, 80)
        write_frames_file(os.path.join(d.ingest_dir, "f0.frames"),
                          [mk(80)] * 40 + [v6] * 10, 10)
        write_frames_file(os.path.join(d.ingest_dir, "f1.frames"),
                          [mk(81)] * 50 + [mk(80)] * 30, 10)
        assert d.process_ingest_once() == 2
        got = {}
        for fn in ("f0", "f1"):
            with open(os.path.join(d.out_dir,
                                   fn + ".frames.verdicts.json")) as f:
                got[fn] = json.load(f)
        assert (got["f0"]["drop"], got["f0"]["pass"]) == (40, 10)
        assert (got["f1"]["drop"], got["f1"]["pass"]) == (30, 50)

        st = d.sched_stats.snapshot()
        assert st["admitted"] == 130 and st["completed"] == 130
        assert st["batches"] >= 3  # family/size split, max_batch=64
        assert max(st["batch_hist"]) <= 64
        # the ladder pre-warm ran once for this table generation
        assert d._prewarmed_gen is not None

        # scheduler + wire-format counters on the metrics endpoint
        port = d.actual_metrics_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            text = r.read().decode()
        assert "scheduler_admitted_packets_total 130" in text
        assert "scheduler_batches_total" in text
        assert "scheduler_deadline_miss_total" in text
        assert "ingressnodefirewall_node_wire_" in text
    finally:
        d.stop()


def test_daemon_scheduler_deadline_miss_events(tmp_path):
    """An unmeetable deadline: every packet misses, the miss counter
    advances, and DeadlineMissRecords land on the daemon's event ring
    (draining to events.log as scheduler lines)."""
    from infw.daemon import write_frames_file
    from infw.obs.pcap import build_frame
    from infw.constants import IPPROTO_TCP

    d = _mk_daemon(tmp_path, deadline_us=0.001, max_batch=32)
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(_node_state_doc(), f)
        d.scan_nodestates_once()
        deny = build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80)
        write_frames_file(os.path.join(d.ingest_dir, "m.frames"),
                          [deny] * 20, 10)
        assert d.process_ingest_once() == 1
        st = d.sched_stats.snapshot()
        assert st["misses"] == 20
        lines = []
        d.events_logger._sink = lines.append
        d.events_logger.drain_once()
        assert any("scheduler deadline-miss" in ln for ln in lines)
    finally:
        d.stop()


def test_daemon_deadline_counts_ingest_dir_queueing(tmp_path):
    """Arrival time is the file's DROP time (mtime), not in-tick parse
    time: a file that sat in the ingest dir behind a busy tick counts
    that wait against its deadline — the coordinated-omission rule."""
    import time as _time

    from infw.daemon import write_frames_file
    from infw.obs.pcap import build_frame
    from infw.constants import IPPROTO_TCP

    d = _mk_daemon(tmp_path, deadline_us=100_000.0, max_batch=64)
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(_node_state_doc(), f)
        d.scan_nodestates_once()
        deny = build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80)
        # warm tick pays the ladder prewarm so later ticks are fast
        write_frames_file(os.path.join(d.ingest_dir, "w.frames"),
                          [deny] * 5, 10)
        d.process_ingest_once()
        m0 = d.sched_stats.snapshot()["misses"]
        # fresh file: classified well inside the 100ms budget
        write_frames_file(os.path.join(d.ingest_dir, "f.frames"),
                          [deny] * 10, 10)
        assert d.process_ingest_once() == 1
        assert d.sched_stats.snapshot()["misses"] == m0
        # stale file: mtime 2s in the past = it queued behind a busy
        # tick; that wait must count, so every packet misses
        p = os.path.join(d.ingest_dir, "s.frames")
        write_frames_file(p, [deny] * 10, 10)
        past = _time.time() - 2.0
        os.utime(p, (past, past))
        assert d.process_ingest_once() == 1
        assert d.sched_stats.snapshot()["misses"] == m0 + 10
    finally:
        d.stop()


def test_daemon_burst_larger_than_max_tick_packets(tmp_path):
    """A burst beyond max_tick_packets spans ticks: the parse-ahead
    bound defers whole files to the next tick, and every packet is
    still classified exactly once."""
    from infw.daemon import write_frames_file
    from infw.obs.pcap import build_frame
    from infw.constants import IPPROTO_TCP

    d = _mk_daemon(tmp_path, deadline_us=200_000.0, max_batch=32,
                   max_tick_packets=50)
    try:
        with open(os.path.join(d.nodestates_dir, f"{NODE}.json"), "w") as f:
            json.dump(_node_state_doc(), f)
        d.scan_nodestates_once()
        deny = build_frame("10.1.2.3", "203.0.113.1", IPPROTO_TCP, 999, 80)
        for i in range(3):
            write_frames_file(
                os.path.join(d.ingest_dir, f"b{i}.frames"), [deny] * 40, 10
            )
        # tick 1 parses ahead to the 50-packet bound: files b0+b1 (the
        # bound is checked before each subsequent file), b2 waits
        assert d.process_ingest_once() == 2
        assert d.process_ingest_once() == 1
        total = 0
        for i in range(3):
            with open(os.path.join(
                d.out_dir, f"b{i}.frames.verdicts.json")) as f:
                total += json.load(f)["packets"]
        assert total == 120
        assert d.sched_stats.snapshot()["completed"] == 120
    finally:
        d.stop()


def test_daemon_legacy_mode_untouched(tmp_path):
    """Without --deadline-us the daemon keeps the fixed-ingest_chunk
    dispatch and constructs no scheduler state."""
    d = _mk_daemon(tmp_path, backend="cpu")
    try:
        assert d.sched_stats is None and d._sched_policy is None
    finally:
        d.stop()


def test_daemon_cli_knob_validation(tmp_path):
    from infw.daemon import main as daemon_main

    with pytest.raises(SystemExit):
        daemon_main(["--state-dir", str(tmp_path), "--node-name", "x",
                     "--deadline-us", "-5"])
    with pytest.raises(SystemExit):
        daemon_main(["--state-dir", str(tmp_path), "--node-name", "x",
                     "--max-batch", "0"])


# --- load generator ----------------------------------------------------------


def test_loadgen_deterministic_and_parseable(tmp_path):
    import importlib.util
    import sys

    tools_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    spec = importlib.util.spec_from_file_location(
        "infw_loadgen", os.path.join(tools_dir, "loadgen.py")
    )
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    out1, out2 = str(tmp_path / "a"), str(tmp_path / "b")
    args = ["--rate", "1000000", "--n", "1500", "--file-packets", "512",
            "--seed", "11"]
    assert lg.main(["--out", out1] + args) == 0
    assert lg.main(["--out", out2] + args) == 0
    files = sorted(f for f in os.listdir(out1) if f.endswith(".frames"))
    assert len(files) == 3
    for fn in files:  # byte-identical across runs: seeded determinism
        assert open(os.path.join(out1, fn), "rb").read() == \
            open(os.path.join(out2, fn), "rb").read()
    with open(os.path.join(out1, "loadgen-manifest.json")) as f:
        man = json.load(f)
    assert man["n"] == 1500 and len(man["file_start_offsets_s"]) == 3

    from infw.daemon import read_frames_any
    from infw.obs.pcap import parse_frames_buf

    fb = read_frames_any(os.path.join(out1, files[0]))
    batch = parse_frames_buf(fb)
    assert len(batch) == 512
    assert (np.asarray(batch.ifindex) == 10).all()

    # burst mode: grouped starts, deterministic too
    out3 = str(tmp_path / "c")
    assert lg.main(["--out", out3, "--rate", "1000000", "--n", "600",
                    "--burst", "64", "--file-packets", "600",
                    "--seed", "5"]) == 0
    assert os.path.exists(os.path.join(out3, "load000000.frames"))
