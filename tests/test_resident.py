"""Zero-copy resident serving loop (ISSUE-12).

Covers: bit-identity of the ONE-fused-program-per-admission dispatch
(decode + flow probe + stateless classify + merge + stats + miss
insert) vs the multi-dispatch flow plan AND the CPU oracle — verdicts,
statistics and all four donated flow columns; donation aliasing
discipline (back-to-back dispatches must not corrupt earlier unread
outputs, incl. under the scheduler's ping-pong staging and on the
8-virtual-device mesh); table-patch staleness (the pool context
refreshes per generation; the injected residentstale defect serves
stale tables and must diverge); the zero-recompile/zero-alloc warm
lifecycle; the ingest ring (wraparound, backpressure, zero-copy views,
loadgen producer subprocess, daemon ring ingest + metrics); the
jaxcheck donation lint both ways; the statecheck resident config; the
native delta-encode parity; and the BENCH_r05 rung-32 pinned-input
regression (compile-free pinned sweep after the ladder prewarm — the
round-5 anomaly was the first-measured shape paying its jit
specialization + per-executable first-dispatch cost inside the timed
loop, not a rung-32 dataplane bug).
"""
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from infw import oracle, resident as resident_mod, testing
from infw.backend.tpu import TpuClassifier
from infw.compiler import IncrementalTables
from infw.flow import FlowConfig
from infw.kernels import jaxpath
from infw.ring import IngestRing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: ONE flow geometry for the whole module (and the same one the
#: entrypoint fixtures use): jitted_resident_step caches key on the
#: slab geometry, so every test sharing it amortizes the fused-program
#: compiles — the suite cost is dominated by unique (geometry, shape)
#: compiles, not by test count.
ENTRIES = 512


def _tables(seed=3, n=300, width=4, v6=0.4):
    return testing.random_tables_fast(
        np.random.default_rng(seed), n_entries=n, width=width,
        v6_fraction=v6, ifindexes=(2, 3),
    )


def _pair(tabs, entries=ENTRIES, **kw):
    """(resident classifier, multi-dispatch classifier), same tables and
    flow geometry."""
    res = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=entries),
        resident=True, **kw,
    )
    multi = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=entries), **kw,
    )
    res.load_tables(tabs)
    multi.load_tables(tabs)
    return res, multi


@pytest.fixture(scope="module")
def shared():
    """Module-shared (tables, resident clf, multi clf), ladder
    pre-warmed once; tests reset the flow tiers instead of rebuilding
    classifiers (each rebuild would re-run the jit warm dispatches)."""
    from infw.scheduler import prewarm_ladder

    tabs = _tables()
    res, multi = _pair(tabs, force_path="trie")
    # identical ladders: every production dispatch bumps the flow epoch
    # exactly once (fused step or classic probe), so equal prewarm
    # sequences keep the two tiers' epoch counters in lockstep — the
    # column bit-identity tests compare se[:, 1] (last-seen epochs) too.
    # Depth-class variants are skipped (one fused compile per class per
    # rung — the tests here never steer); the full-ladder prewarm is
    # exercised by bench_resident and the scheduler suite.
    prewarm_ladder(res, (32, 64, 128), include_depth_classes=False)
    prewarm_ladder(multi, (32, 64, 128), include_depth_classes=False)
    yield tabs, res, multi
    res.close()
    multi.close()


def _flow_cols(clf):
    return clf.flow.flow_columns()


# --- fused-step bit-identity -------------------------------------------------


@pytest.mark.slow
def test_resident_bit_identity_vs_multi_and_oracle(shared):
    """Two passes (populate, then serve-from-cache) over the same batch:
    verdicts, xdp, statistics and every donated flow column must equal
    the multi-dispatch plan and the CPU oracle at each pass."""
    tabs, res, multi = shared
    res.flow.reset()
    multi.flow.reset()
    batch = testing.random_batch_fast(np.random.default_rng(9), tabs, 64)
    ref = oracle.classify(tabs, batch)
    for p in range(2):
        o = res.classify(batch, apply_stats=False)
        om = multi.classify(batch, apply_stats=False)
        assert np.array_equal(o.results, ref.results), f"pass {p}"
        assert np.array_equal(o.xdp, ref.xdp)
        assert np.array_equal(o.stats_delta, om.stats_delta)
        from infw.testing import stats_dict_from_array

        assert stats_dict_from_array(o.stats_delta) == ref.stats
    a, b = _flow_cols(res), _flow_cols(multi)
    for k in a:
        assert np.array_equal(a[k], b[k]), f"flow column {k} diverged"
    assert res.resident_counters()["resident_dispatches_total"] >= 2


@pytest.mark.slow
def test_resident_tcp_flags_and_v4_compact(shared):
    """The SYN/EST/FIN/RST state machine rides the fused step: a flagged
    trace through the resident path matches the multi-dispatch plan
    column-for-column, on the v4-compact 4-word wire."""
    tabs = _tables(v6=0.0)
    res, multi = _pair(tabs, force_path="trie")
    batch, _meta = testing.flow_trace_batch(
        np.random.default_rng(17), tabs, 256, 0.7, chunk_packets=64
    )
    ref = oracle.classify(tabs, batch)
    for lo in range(0, len(batch), 64):
        sub = batch.slice(lo, lo + 64)
        o = res.classify(sub, apply_stats=False)
        om = multi.classify(sub, apply_stats=False)
        assert np.array_equal(o.results, ref.results[lo : lo + 64])
        assert np.array_equal(o.results, om.results)
    a, b = _flow_cols(res), _flow_cols(multi)
    for k in a:
        assert np.array_equal(a[k], b[k]), f"flow column {k} diverged"


@pytest.mark.slow
def test_resident_dense_and_ctrie_paths():
    """The resident program covers all three layout paths: the dense
    path serves from the pool's XLA DeviceTables twin, the compressed
    layout from the ctrie walk — both oracle-identical."""
    for kw, seed in (({}, 5), ({"force_path": "ctrie"}, 7)):
        tabs = _tables(seed=seed)
        res, _m = _pair(tabs, **kw)
        batch = testing.random_batch_fast(
            np.random.default_rng(seed + 1), tabs, 64
        )
        ref = oracle.classify(tabs, batch)
        for _ in range(2):
            o = res.classify(batch, apply_stats=False)
            assert np.array_equal(o.results, ref.results)
        assert res.resident_counters()["resident_fallbacks_total"] == 0
        _m.close()
        res.close()


@pytest.mark.slow
def test_resident_overlay_variants():
    """The overlay side-table combine rides the fused step (trie and
    compressed layouts): overlay-resident keys win by longest prefix,
    oracle-identical across both passes."""
    from infw.compiler import compile_tables_from_content

    tabs = _tables(seed=13, n=200)
    ov_tabs = testing.random_tables_fast(
        np.random.default_rng(14), n_entries=8, width=4, v6_fraction=0.3,
        ifindexes=(2, 3),
    )
    taken = {k.masked_identity() for k in tabs.content}
    ov_content = {
        k: v for k, v in ov_tabs.content.items()
        if k.masked_identity() not in taken
    }
    ov = compile_tables_from_content(ov_content, rule_width=4)
    merged = dict(tabs.content)
    merged.update(ov_content)
    model = compile_tables_from_content(merged, rule_width=4)
    for fp in ("trie", "ctrie"):
        clf = TpuClassifier(
            interpret=True, force_path=fp,
            flow_table=FlowConfig.make(entries=512), resident=True,
        )
        clf.load_tables(tabs, overlay=ov)
        batch = testing.random_batch(np.random.default_rng(15), model, 64)
        ref = oracle.classify(model, batch)
        for p in range(2):
            o = clf.classify(batch, apply_stats=False)
            assert np.array_equal(o.results, ref.results), (fp, p)
        assert clf.resident_counters()["resident_fallbacks_total"] == 0
        clf.close()


@pytest.mark.slow
def test_resident_wide_ruleid_falls_back():
    """Wide-ruleId tables cannot ride the 16-bit resident merge: the
    classifier falls back to the full-batch u32 path, verdicts stay
    oracle-identical (degrade, never refuse)."""
    from infw.constants import IPPROTO_TCP

    content = dict(_tables(n=64).content)
    k = next(iter(content))
    rows = np.zeros((4, 7), np.int32)
    rows[1] = [70001, IPPROTO_TCP, 443, 0, 0, 0, 1]
    content[k] = rows
    from infw.compiler import compile_tables_from_content

    tabs = compile_tables_from_content(content, rule_width=4)
    res = TpuClassifier(
        interpret=True, flow_table=FlowConfig.make(entries=512),
        resident=True, force_path="trie",
    )
    res.load_tables(tabs)
    batch = testing.random_batch(np.random.default_rng(3), tabs, 64)
    ref = oracle.classify(tabs, batch)
    o = res.classify(batch, apply_stats=False)
    assert np.array_equal(o.results, ref.results)
    res.close()


# --- donation / aliasing discipline -----------------------------------------


@pytest.mark.slow
def test_resident_back_to_back_unread_outputs(shared):
    """Double-buffer discipline: dispatch N+1 reusing the donated pool
    must not corrupt dispatch N's unread output — stage several plans
    back-to-back, materialize them afterwards in order and out of
    order."""
    tabs, res, _multi = shared
    res.flow.reset()
    rng = np.random.default_rng(23)
    batches = [testing.random_batch_fast(rng, tabs, 32) for _ in range(6)]
    refs = [oracle.classify(tabs, b) for b in batches]
    plans = []
    for b in batches:
        wire = b.pack_wire()
        plans.append(
            (res.prepare_packed(wire, False), b)
        )
    # materialize out of dispatch order: 3, 0, 5, 1, 4, 2
    for i in (3, 0, 5, 1, 4, 2):
        out = res.classify_prepared(plans[i][0], apply_stats=False).result()
        assert np.array_equal(out.results, refs[i].results), f"plan {i}"


@pytest.mark.slow
def test_resident_scheduler_ping_pong_staging(shared):
    """The continuous scheduler's prepare/launch ping-pong over the
    resident path: staged resident plans chain the donated buffers in
    dispatch order; served verdicts stay oracle-identical."""
    from infw.scheduler import (
        ContinuousScheduler, DeadlinePolicy, ServiceModel,
    )

    tabs, res, _multi = shared
    res.flow.reset()
    batch = testing.random_batch_fast(np.random.default_rng(31), tabs, 600)
    ref = oracle.classify(tabs, batch)
    sched = ContinuousScheduler(
        res, DeadlinePolicy(0.5, 128, service=ServiceModel()),
        pipeline_depth=3, stage_depth=2,
    )
    out = sched.serve(batch, np.zeros(len(batch)))
    assert np.array_equal(out.results, ref.results)


@pytest.mark.slow
def test_resident_mesh_parity():
    """The mesh classifier inherits the resident path via the same
    jitted factories (GSPMD over the replicated placement): parity vs
    the CPU oracle on the 8-virtual-device pool."""
    from infw.backend.mesh import MeshTpuClassifier

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device pool")
    tabs = _tables()
    clf = MeshTpuClassifier(
        data_shards=4, rules_shards=1, interpret=True, force_path="trie",
        flow_table=FlowConfig.make(entries=512), resident=True,
    )
    clf.load_tables(tabs)
    batch = testing.random_batch_fast(np.random.default_rng(5), tabs, 64)
    ref = oracle.classify(tabs, batch)
    for _ in range(2):
        o = clf.classify(batch, apply_stats=False)
        assert np.array_equal(o.results, ref.results)
    assert clf.resident_counters()["resident_dispatches_total"] >= 2
    clf.close()


# --- staleness: patches refresh the pool context -----------------------------


@pytest.mark.slow
def test_resident_serves_new_tables_after_patch(shared):
    tabs, _r, _m = shared
    res, _m2 = _pair(tabs, force_path="trie")
    _m2.close()
    batch = testing.random_batch_fast(np.random.default_rng(41), tabs, 64)
    res.classify(batch, apply_stats=False)  # populate the cache
    inc = IncrementalTables.from_content(dict(tabs.content), rule_width=4)
    k = next(iter(tabs.content))
    inc.apply({}, [k])
    snap = inc.snapshot()
    res.load_tables(snap, dirty_hint=inc.peek_dirty())
    ref = oracle.classify(snap, batch)
    o = res.classify(batch, apply_stats=False)
    assert np.array_equal(o.results, ref.results), (
        "resident path served stale tables after an incremental patch"
    )
    res.close()


@pytest.mark.slow
def test_resident_stale_defect_diverges(shared):
    """The injected residentstale defect (dropped generation refresh on
    the pool context) must produce oracle divergence after a patch —
    the signal the statecheck acceptance shrinks on."""
    tabs = shared[0]
    batch = testing.random_batch_fast(np.random.default_rng(41), tabs, 64)
    inc = IncrementalTables.from_content(dict(tabs.content), rule_width=4)
    # delete every entry: the post-patch oracle must diverge somewhere
    inc.apply({}, list(tabs.content))
    snap = inc.snapshot()
    resident_mod._INJECT_RESIDENT_STALE_BUG = True
    try:
        res, _m = _pair(tabs, force_path="trie")
        _m.close()
        res.classify(batch, apply_stats=False)
        res.load_tables(snap)
        ref = oracle.classify(snap, batch)
        o = res.classify(batch, apply_stats=False)
        assert not np.array_equal(o.results, ref.results), (
            "injected stale-context defect did not diverge"
        )
        res.close()
    finally:
        resident_mod._INJECT_RESIDENT_STALE_BUG = False


# --- zero-recompile / zero-alloc lifecycle ----------------------------------


@pytest.mark.slow
def test_resident_zero_recompile_zero_alloc_steady_state(shared):
    tabs, res, _multi = shared
    res.flow.reset()
    res.mark_resident_warm()
    cfg = res.flow.config
    fns = [
        jaxpath.jitted_resident_step(cfg.entries, cfg.ways, "trie",
                                     v4, None, 0, False)
        for v4 in (False, True)
    ]
    cache0 = sum(f._cache_size() for f in fns)
    batch = testing.random_batch_fast(np.random.default_rng(51), tabs, 64)
    w7 = batch.pack_wire()
    v4b = batch.take(np.nonzero(np.asarray(batch.kind) != 2)[0])
    v4b.ip_words[:, 1:] = 0
    w4 = v4b.pack_wire_v4()[:32]
    for i in range(50):
        res.classify_prepared(
            res.prepare_packed(w7[:64], False), apply_stats=False
        ).result()
        res.classify_prepared(
            res.prepare_packed(w4, True), apply_stats=False
        ).result()
    grew = sum(f._cache_size() for f in fns) - cache0
    assert grew == 0, f"{grew} resident recompiles on the warm lifecycle"
    assert res.resident.steady_allocs() == 0, (
        f"{res.resident.steady_allocs()} pool allocations on the warmed "
        "serving path"
    )


@pytest.mark.slow
def test_rung32_pinned_input_regression(shared):
    """BENCH_r05 anomaly pin (ISSUE-12 satellite): the round-5 record's
    11.77 ms pinned-input p50 @batch=32 beside 0.25 ms @batch=128 was a
    measurement artifact — the ladder's FIRST-measured shape (32) paid
    its jit specialization plus the tunnel's per-executable
    first-dispatch cost inside the timed loop, not a rung-32 dataplane
    bug.  The fix is the full-ladder prewarm before any timed sample;
    this test pins it with the _cache_size lint: after the prewarm, a
    pinned-device-input sweep at 32/64/128 (the r05 shapes, dense wire
    path AND the resident serving path) must perform ZERO compiles, so
    nothing shape-driven can ever land inside a timed rung again."""
    tabs, res, _multi = shared
    res.flow.reset()
    # the bench_wire_latency dense-wire factory (the r05 tier's path)
    dt = jaxpath.device_tables(tabs)
    fn_wire = jaxpath.jitted_classify_wire(False)
    for bs in (32, 64, 128):
        w = jax.device_put(
            testing.random_batch_fast(
                np.random.default_rng(bs), tabs, bs
            ).pack_wire()
        )
        np.asarray(fn_wire(dt, w)[0])
    cfg = res.flow.config
    fns = [fn_wire] + [
        jaxpath.jitted_resident_step(cfg.entries, cfg.ways, "trie",
                                     v4, None, 0, False)
        for v4 in (False, True)
    ]
    cache0 = sum(f._cache_size() for f in fns)
    for bs in (32, 64, 128):
        batch = testing.random_batch_fast(
            np.random.default_rng(100 + bs), tabs, bs
        )
        w_np = batch.pack_wire()
        dw = jax.device_put(w_np)  # pinned device input
        for _ in range(3):
            np.asarray(fn_wire(dt, dw)[0])
            res.classify_prepared(
                res.prepare_packed(w_np, False), apply_stats=False
            ).result()
    grew = sum(f._cache_size() for f in fns) - cache0
    assert grew == 0, (
        f"{grew} compiles during the pinned-input sweep — the BENCH_r05 "
        "anomaly condition (first-dispatch cost inside a timed rung) "
        "has regressed"
    )


# --- ingest ring -------------------------------------------------------------


def test_ring_roundtrip_wraparound_flags(tmp_path):
    p = str(tmp_path / "r.ring")
    ring = IngestRing.create(p, slots=4, slot_packets=64)
    prod = IngestRing.attach(p)
    for i in range(11):
        w = np.full((16, 4 if i % 2 else 7), i, np.uint32)
        fl = np.full(16, i, np.int32) if i % 3 == 0 else None
        prod.push(w, v4_only=(i % 2 == 1), tcp_flags=fl)
        c = ring.pop(timeout=2.0)
        assert c is not None
        assert np.array_equal(c.wire, w)
        assert c.v4_only == (i % 2 == 1)
        assert (c.tcp_flags is None) == (i % 3 != 0)
        if c.tcp_flags is not None:
            assert (c.tcp_flags == i).all()
        c.release()
    assert ring.pop(timeout=0.05) is None
    ring.close()
    prod.close()


def test_ring_backpressure_and_slot_hold(tmp_path):
    """A full ring blocks the producer; a popped-but-unreleased chunk's
    slot is NOT reclaimed (its views double as H2D staging buffers)."""
    import threading
    import time as _t

    p = str(tmp_path / "r.ring")
    ring = IngestRing.create(p, slots=2, slot_packets=16)
    prod = IngestRing.attach(p)
    prod.push(np.full((4, 4), 1, np.uint32))
    prod.push(np.full((4, 4), 2, np.uint32))
    with pytest.raises(TimeoutError):
        prod.push(np.full((4, 4), 3, np.uint32), timeout=0.05)
    held = ring.pop(timeout=1.0)
    # tail has NOT advanced: the producer still blocks
    with pytest.raises(TimeoutError):
        prod.push(np.full((4, 4), 3, np.uint32), timeout=0.05)
    view_before = held.wire.copy()
    t = threading.Thread(
        target=lambda: prod.push(np.full((4, 4), 3, np.uint32),
                                 timeout=2.0)
    )
    t.start()
    _t.sleep(0.05)
    held.release()
    t.join(timeout=2.0)
    assert not t.is_alive()
    # the held view was never overwritten while in flight
    assert np.array_equal(view_before, np.full((4, 4), 1, np.uint32))
    for want in (2, 3):
        c = ring.pop(timeout=1.0)
        assert (c.wire == want).all()
        c.release()
    ring.close()
    prod.close()


def test_ring_flagless_record_at_full_capacity(tmp_path):
    """Review finding: pop()'s sanity bound must use the RECORD's own
    layout — a flag-less record legally holds more packets than a
    flagged one of the same slot size and must not be dropped as
    corrupt."""
    p = str(tmp_path / "r.ring")
    ring = IngestRing.create(p, slots=2, slot_packets=64)
    n = ring.max_packets(4, with_flags=False)
    assert n > ring.max_packets(4, with_flags=True)
    ring.push(np.full((n, 4), 9, np.uint32))
    c = ring.pop(timeout=1.0)
    assert c is not None and c.wire.shape == (n, 4) and (c.wire == 9).all()
    c.release()
    ring.close()


def test_ring_corrupt_record_preserves_inflight_slots(tmp_path):
    """Review finding: a poison (corrupt) record must advance only the
    READ cursor — the tail (producer-visible free boundary) moves past
    it only when the in-order release protocol reaches it, so earlier
    popped-but-unreleased slot views are never overwritten and later
    releases never wedge."""
    p = str(tmp_path / "r.ring")
    ring = IngestRing.create(p, slots=4, slot_packets=16)
    ring.push(np.full((4, 4), 1, np.uint32))
    ring.push(np.full((4, 4), 2, np.uint32))
    ring.push(np.full((4, 4), 3, np.uint32))
    held = ring.pop(timeout=1.0)  # seq 0, unreleased (in-flight H2D)
    # corrupt record 1 in place (impossible width)
    off = ring._slot_off(1)
    np.frombuffer(ring._mm, np.uint32, 4, off + 8)[1] = 99
    with pytest.raises(ValueError):
        ring.pop(timeout=0.1)
    # the tail must NOT have jumped past the in-flight seq-0 slot
    assert ring.tail == 0
    ok = ring.pop(timeout=1.0)  # seq 2 still readable
    assert (ok.wire == 3).all()
    # releases proceed in order and drain through the poison slot
    held.release()
    assert ring.tail == 2  # 0 released, poison 1 drained through
    ok.release()
    assert ring.tail == 3
    ring.close()


def test_loadgen_ring_producer_deterministic(tmp_path):
    """tools/loadgen.py --ring drives a real ring from a subprocess;
    two runs with the same seed produce byte-identical record streams."""
    streams = []
    for run in range(2):
        p = str(tmp_path / f"lg{run}.ring")
        ring = IngestRing.create(p, slots=64, slot_packets=256)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--ring", p, "--rate", "1e6", "--n", "1024",
             "--file-packets", "256", "--seed", "11", "--ifindex", "2"],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        recs = []
        while True:
            c = ring.pop(timeout=0.2)
            if c is None:
                break
            recs.append((c.wire.copy(), c.v4_only))
            c.release()
        assert sum(len(w) for w, _ in recs) == 1024
        streams.append(recs)
        ring.close()
    for (wa, va), (wb, vb) in zip(*streams):
        assert va == vb and np.array_equal(wa, wb)


@pytest.mark.slow
def test_daemon_ring_ingest_resident(tmp_path):
    """Daemon --ring mode: records pushed by a producer are classified
    through the resident path; ring_* and resident_* gauges export on
    /metrics; slots release after materialize."""
    from infw.daemon import Daemon

    ringp = str(tmp_path / "ingest.ring")
    daemon = Daemon(
        state_dir=str(tmp_path), node_name="n1", backend="tpu",
        resident=True, ring=ringp, metrics_port=0, health_port=0,
        file_poll_interval_s=10.0,
        flow_table=FlowConfig.make(entries=ENTRIES),
    )
    try:
        tabs = _tables()
        clf = daemon.syncer._factory()
        clf.load_tables(tabs)
        daemon.syncer._classifier = clf
        assert clf.resident is not None
        prod = IngestRing.attach(ringp)
        batch = testing.random_batch_fast(
            np.random.default_rng(61), tabs, 256
        )
        for lo in range(0, 256, 64):
            w, v4 = batch.pack_wire_subset(
                np.arange(lo, lo + 64, dtype=np.int64)
            )
            prod.push(w, v4_only=v4)
        n = daemon.process_ring_once(budget=10**9)
        assert n == 256
        assert daemon.ingest_ring.tail == daemon.ingest_ring.head
        text = daemon.metrics_registry.render_text()
        assert "ring_popped_total 4" in text
        assert "resident_dispatches_total" in text
        # stats landed exactly once (apply_stats=True on the ring path)
        snap = clf.stats.snapshot()  # (MAX_TARGETS, 4) int64
        ref = oracle.classify(tabs, batch)
        from infw.testing import stats_dict_from_array

        assert stats_dict_from_array(snap) == ref.stats
        prod.close()
    finally:
        daemon.stop()


def test_daemon_resident_flag_validation(tmp_path):
    """Launch validation: --resident on the cpu backend is a usage
    error; --ring into a missing directory is a usage error."""
    from infw.daemon import main as daemon_main

    with pytest.raises(SystemExit) as e:
        daemon_main(["--state-dir", str(tmp_path), "--node-name", "n",
                     "--backend", "cpu", "--resident"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        daemon_main(["--state-dir", str(tmp_path), "--node-name", "n",
                     "--backend", "tpu",
                     "--ring", str(tmp_path / "no" / "dir" / "x.ring")])
    assert e.value.code == 2


# --- donation lint -----------------------------------------------------------


@pytest.mark.slow
def test_donation_lint_passes_on_resident_entrypoints():
    from infw.analysis import jaxcheck
    from infw.kernels import kernel_entrypoints

    eps = {e.name: e for e in kernel_entrypoints()}
    assert "classify-wire/resident-fused" in eps
    assert "classify-wire/resident-ring-fused" in eps
    for name in ("classify-wire/resident-fused",
                 "classify-wire/resident-ring-fused"):
        ep = eps[name]
        assert ep.donate == (0, 3)
        findings = jaxcheck._donation_lint(ep, (64,))
        errs = [f for f in findings if f.severity == "error"]
        assert not errs, errs


def test_donation_lint_fails_on_defect_and_undeclared():
    from infw.analysis import jaxcheck
    from infw.kernels import KernelEntrypoint

    ep = jaxcheck.donation_defect_entrypoint()
    findings = jaxcheck._donation_lint(ep, (64,))
    assert any(
        f.check == "donation" and f.severity == "error" for f in findings
    ), "declared-but-unaliasable donation not flagged"
    # a resident-named entrypoint with no donate declaration is an error
    bare = KernelEntrypoint(
        "classify-wire/resident-undeclared", "xla",
        lambda b: (None, ()),
    )
    findings = jaxcheck._donation_lint(bare, (64,))
    assert any(f.severity == "error" for f in findings)


# --- statecheck resident config ---------------------------------------------


def test_statecheck_resident_config_registered():
    """The resident config is registered and resolvable (the full
    equivalence run is tier-gated: `make state-check` and the
    resident-bench gate both execute run_config('resident'); the slow
    tier runs it in-suite too)."""
    from infw.analysis import statecheck

    cfg = statecheck.CONFIGS["resident"]
    assert cfg.resident and cfg.flow > 0


@pytest.mark.slow
def test_statecheck_resident_config_green():
    from infw.analysis import statecheck

    rep = statecheck.run_config("resident", seed=1, n_ops=5,
                                shrink_on_failure=False)
    assert rep["ok"], rep.get("failure")


@pytest.mark.slow
def test_statecheck_residentstale_defect_caught():
    from infw.analysis import statecheck

    resident_mod._INJECT_RESIDENT_STALE_BUG = True
    try:
        rep = statecheck.run_config("resident", seed=0, n_ops=12,
                                    shrink_on_failure=True,
                                    max_shrink_runs=48)
    finally:
        resident_mod._INJECT_RESIDENT_STALE_BUG = False
    assert not rep["ok"], "injected residentstale defect not caught"
    assert rep["shrunk"]["ops"] <= 3


# --- native delta-encode parity ---------------------------------------------


def test_native_delta_encode_parity():
    """The C++ single-pass delta encoder must be byte-identical to the
    NumPy reference across dictionary modes, fixed/varint plans and the
    auto gate (skips when the native library is unavailable)."""
    import infw.packets as pk

    try:
        from infw.backend.cpu_ref import load_library

        load_library()
    except Exception:
        pytest.skip("native library unavailable")

    def numpy_encode(w, cap=None):
        old = pk._native_delta_unavailable
        pk._native_delta_unavailable = True
        try:
            return pk.encode_delta_wire(w, cap)
        finally:
            pk._native_delta_unavailable = old

    from infw.packets import PacketBatch

    checked = 0
    for seed in range(12):
        r = np.random.default_rng(seed)
        n = int(r.integers(1, 400))
        b = PacketBatch(
            kind=np.ones(n, np.int32),
            l4_ok=np.ones(n, np.int32),
            ifindex=r.integers(1, 1 + [1, 3, 15, 16][seed % 4], n).astype(
                np.int32
            ),
            ip_words=np.concatenate(
                [r.integers(0, [100, 1 << 16, 1 << 30][seed % 3],
                            (n, 1)).astype(np.uint32),
                 np.zeros((n, 3), np.uint32)], axis=1,
            ),
            proto=np.asarray([6, 17, 1, 58], np.int32)[
                r.integers(0, 4, n)
            ],
            dst_port=r.integers(0, [1, 40, 70000][seed % 3], n).astype(
                np.int32
            ),
            icmp_type=r.integers(0, 4, n).astype(np.int32),
            icmp_code=r.integers(0, 3, n).astype(np.int32),
            pkt_len=r.integers(60, 1500, n).astype(np.int32),
        )
        w = b.pack_wire_v4()
        for cap in (None, 8.0, 1.0):
            a = pk._encode_delta_native(w, cap)
            ref = numpy_encode(w, cap)
            assert (a is None) == (ref is None), (seed, cap)
            if a is None:
                continue
            for f in ("payload", "dict_vals", "ifmap", "perm"):
                assert np.array_equal(getattr(a, f), getattr(ref, f)), (
                    seed, cap, f,
                )
            assert (a.n, a.dict_mode, a.fixed_w, a.crc) == (
                ref.n, ref.dict_mode, ref.fixed_w, ref.crc,
            )
            checked += 1
    assert checked >= 10


# --- device stats twin -------------------------------------------------------


def test_result_stats_matches_host_stats():
    """jaxpath.result_stats (the in-program stats the fused paths use)
    must merge to exactly daemon.stats_from_results on the same
    verdicts + pkt_len (the wire8/resident readback contract)."""
    from infw.daemon import stats_from_results

    tabs = _tables()
    batch = testing.random_batch_fast(np.random.default_rng(71), tabs, 256)
    ref = oracle.classify(tabs, batch)
    db = jaxpath.device_batch(batch)
    dev = jax.jit(jaxpath.result_stats)(
        jax.device_put(ref.results.astype(np.uint32)), db
    )
    merged = jaxpath.merge_stats_host(np.asarray(dev))
    host = stats_from_results(ref.results, np.asarray(batch.pkt_len))
    assert np.array_equal(merged, host)
