import json, os, sys, time, urllib.request
sys.path.insert(0, "/root/repo")
import numpy as np
from infw.daemon import write_frames_file_v2
from infw.obs.pcap import build_frame, FramesBuf
sd = "/tmp/infw-verify4/state"
ns = {"apiVersion": "ingressnodefirewall.openshift.io/v1alpha1",
      "kind": "IngressNodeFirewallNodeState",
      "metadata": {"name": os.uname().nodename, "namespace": "ingress-node-firewall-system"},
      "spec": {"interfaceIngressRules": {"eth0": [
          {"sourceCIDRs": ["10.1.0.0/16"],
           "rules": [{"order": 1, "protocolConfig": {"protocol": "TCP",
                      "tcp": {"ports": "80"}}, "action": "Deny"}]}]}}}
p = os.path.join(sd, "nodestates", os.uname().nodename + ".json")
with open(p + ".tmp", "w") as f: json.dump(ns, f)
os.replace(p + ".tmp", p)
time.sleep(3)
fb = FramesBuf.from_frames([build_frame("10.1.2.3", "9.9.9.9", 6, 1234, 80)], 2)
write_frames_file_v2(os.path.join(sd, "ingest", "v.frames"), fb)
deadline = time.time() + 20
vp = os.path.join(sd, "out", "v.frames.verdicts.json")
while time.time() < deadline and not os.path.exists(vp): time.sleep(0.1)
print("verdicts:", open(vp).read())
print("healthz:", urllib.request.urlopen("http://127.0.0.1:39300/healthz").read())
