#!/usr/bin/env python
"""Bundle-driven composition launcher.

The OLM bundle analogue made operational: where the reference's bundle/
ClusterServiceVersion tells OLM how to install and run the operator
(/root/reference/bundle/manifests/ingress-node-firewall.clusterserviceversion.yaml
declares the deployments, env contract and RBAC), this launcher READS
``deploy/bundle/manifest.json`` and brings up the declared composition —
events sidecar, manager (fan-out + apply dir), daemon (dataplane) — as
supervised processes wired through a shared state dir and events socket,
exactly like the reference daemonset wires its three containers
(bindata/manifests/daemon/daemonset.yaml:25-113).

Usage:
    python deploy/launch.py --state-dir /var/lib/infw [--backend tpu|cpu]
        [--node-name NAME] [--dry-run]

The component commands, their order, and the env contract all come from
the bundle; nothing here hand-rolls a run line.  Required env vars that
have well-known deployment defaults (DAEMONSET_IMAGE etc.) are defaulted
the way the kustomize overlays default them; any remaining missing
required var is a launch error naming the component and variable.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shlex
import signal
import subprocess
import sys
import time

BUNDLE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bundle", "manifest.json")
REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: deployment defaults for required env (the kustomize overlay role);
#: anything already in the environment wins
ENV_DEFAULTS = {
    "DAEMONSET_IMAGE": "infw:latest",
    "DAEMONSET_NAMESPACE": "ingress-node-firewall-system",
}


def load_bundle(path: str = BUNDLE_PATH) -> dict:
    with open(path) as f:
        bundle = json.load(f)
    if bundle.get("schema") != "infw.bundle/v1":
        raise SystemExit(f"{path}: unsupported bundle schema "
                         f"{bundle.get('schema')!r}")
    return bundle


def build_plan(bundle: dict, subs: dict, extra_args: dict | None = None,
               only: str | None = None, flag_env: dict | None = None,
               include: list | None = None):
    """[(name, argv, env)] in bundle launch order.  ``subs`` fills the
    run templates' <placeholders>; ``extra_args`` appends per-component
    argv (e.g. ephemeral ports for tests).  ``only`` selects a single
    component by name (the way a DaemonSet pod runs one declared
    container) — required for components marked ``standalone`` (e.g.
    daemon-multihost), which never join the default composition.
    ``include`` appends explicitly requested standalone components to
    the default order (--with-metrics-proxy): an explicit request is the
    same consent --component gives, so the standalone guard exempts
    them.  ``flag_env`` maps launcher flag names to values; a
    component's ``envFromFlags`` contract routes them into its
    environment."""
    components = bundle["components"]
    include = list(include or [])
    if only is not None:
        if only not in components:
            raise SystemExit(
                f"unknown component {only!r}; bundle declares "
                f"{sorted(components)}"
            )
        order = [only]
    else:
        order = list(bundle.get("launchOrder", sorted(components)))
        order += [n for n in include if n not in order]
    unknown = [n for n in order if n not in components]
    if unknown:
        raise SystemExit(f"bundle launchOrder names unknown components: {unknown}")
    # standalone components (daemon-multihost) carry an env contract the
    # default composition cannot satisfy — launching one there would hang
    # a distributed job on a rank that never joins; they are reachable
    # only through an explicit --component selection (or an explicit
    # ``include`` request).
    standalone_in_order = [
        n for n in order
        if components[n].get("standalone") and n != only and n not in include
    ]
    if standalone_in_order:
        raise SystemExit(
            f"standalone components {standalone_in_order} cannot join the "
            "default composition; launch them with --component"
        )
    # Conversely: multihost flags with no component consuming them would
    # silently launch a single-host plan while the coordinator waits for
    # this rank forever.
    if flag_env:
        consumed = {
            f for n in order
            for f in components[n].get("envFromFlags", {}).values()
        }
        dropped = sorted(
            f for f, v in flag_env.items()
            if v is not None and f not in consumed
        )
        if dropped:
            raise SystemExit(
                f"flags --{' --'.join(dropped)} are not consumed by any "
                "launched component (did you mean --component "
                "daemon-multihost?)"
            )
    plan = []
    for name in order:
        comp = components[name]
        # Split FIRST, substitute per token: a state dir or node name
        # containing spaces/quotes must stay one argv element (the shell
        # script this replaces quoted \"$STATE_DIR\" at every use).
        argv = []
        for tok in shlex.split(comp["run"]):
            # Detect placeholders on the TEMPLATE token, before
            # substitution: a substituted value that itself contains
            # angle brackets (a path, a node name) must not trip a
            # false "unfilled placeholder" error.
            unfilled = [
                m for m in re.findall(r"<([A-Za-z][A-Za-z0-9_-]*)>", tok)
                if m not in subs
            ]
            if unfilled:
                raise SystemExit(
                    f"component {name}: unfilled placeholder "
                    f"{', '.join(f'<{m}>' for m in unfilled)} in run "
                    f"token: {tok}"
                )
            for key, val in subs.items():
                tok = tok.replace(f"<{key}>", str(val))
            argv.append(tok)
        argv[0] = sys.executable  # the bundle says "python"; use ours
        env = dict(os.environ)
        # Override, don't setdefault: --node-name must name the WHOLE
        # composition — a stray exported NODE_NAME would otherwise split
        # it (manager registers --node-name while the daemon reads env
        # and polls for a NodeState that never appears).
        if subs.get("node-name"):
            env["NODE_NAME"] = str(subs["node-name"])
        for var, default in ENV_DEFAULTS.items():
            env.setdefault(var, default)
        # The bundle's envFromFlags contract: launcher flags become the
        # component's env (the daemonset fieldRef/env-injection role) —
        # an explicit flag beats an inherited environment variable.
        for var, flag in comp.get("envFromFlags", {}).items():
            val = (flag_env or {}).get(flag)
            if val is not None:
                env[var] = str(val)
        missing = [
            var for var in comp.get("env", {}).get("required", [])
            if not env.get(var)
        ]
        if missing:
            raise SystemExit(
                f"component {name}: missing required env {missing} "
                "(bundle env contract)"
            )
        argv += (extra_args or {}).get(name, [])
        plan.append((name, argv, env))
    return plan


def launch(plan, state_dir: str) -> int:
    """Spawn the plan in order; supervise until ANY component exits (the
    pod restart-policy model: the composition lives and dies as a unit,
    and an external supervisor restarts the whole thing) or a signal
    arrives, then tear everything down in reverse order."""
    os.makedirs(state_dir, exist_ok=True)
    procs = []

    def teardown(*_a):
        for name, p in reversed(procs):
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 15
        for name, p in reversed(procs):
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    signal.signal(signal.SIGTERM, lambda *_a: sys.exit(143))
    try:
        for name, argv, env in plan:
            log_path = os.path.join(state_dir, f"{name}.log")
            with open(log_path, "ab") as lf:
                p = subprocess.Popen(
                    argv, env=env, cwd=REPO_DIR,
                    stdout=lf, stderr=subprocess.STDOUT,
                )
            procs.append((name, p))
            print(f"launch: {name} pid={p.pid} log={log_path}", flush=True)
        # supervise: if ANY component dies, bring the composition down
        # (the pod restart-policy role; an external supervisor restarts us)
        while True:
            for name, p in procs:
                rc = p.poll()
                if rc is not None:
                    print(f"launch: {name} exited rc={rc}; tearing down",
                          flush=True)
                    return rc
            time.sleep(0.3)
    except (KeyboardInterrupt, SystemExit) as e:
        return int(getattr(e, "code", 130) or 0)
    finally:
        teardown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--state-dir", default="/var/lib/infw")
    ap.add_argument("--backend", default=os.environ.get("INFW_BACKEND", "tpu"),
                    choices=("tpu", "cpu"))
    ap.add_argument("--node-name",
                    default=os.environ.get("NODE_NAME") or os.uname().nodename)
    ap.add_argument("--events-socket", default=None,
                    help="default: <state-dir>/events.sock")
    ap.add_argument("--bundle", default=BUNDLE_PATH)
    ap.add_argument("--ephemeral-ports", action="store_true",
                    help="bind daemon metrics/health to ephemeral ports "
                         "(tests / multiple compositions per host)")
    ap.add_argument("--component", default=None,
                    help="launch ONLY this bundle component (required for "
                         "standalone components, e.g. daemon-multihost)")
    ap.add_argument("--with-metrics-proxy", action="store_true",
                    help="add the authenticated metrics proxy to the "
                         "composition (TLS on by default — a self-signed "
                         "pair is minted under <state-dir>/tls when no "
                         "operator pair exists)")
    ap.add_argument("--insecure-metrics", action="store_true",
                    # a security knob must not misparse common spellings
                    # (False/NO/off) in the insecure direction:
                    # case-insensitive, "off" included
                    default=os.environ.get("INFW_INSECURE_METRICS", "")
                    .strip().lower()
                    not in ("", "0", "false", "no", "off"),
                    help="serve the metrics proxy over PLAINTEXT (the "
                         "bearer token then travels in the clear) — an "
                         "explicit opt-out of the default-on TLS; also "
                         "via INFW_INSECURE_METRICS=1")
    ap.add_argument("--coordinator", default=None,
                    help="multihost: coordinator host:port "
                         "(bundle envFromFlags -> INFW_COORDINATOR)")
    ap.add_argument("--num-processes", default=None,
                    help="multihost: total process count "
                         "(-> INFW_NUM_PROCESSES)")
    ap.add_argument("--process-id", default=None,
                    help="multihost: this host's rank (-> INFW_PROCESS_ID)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the launch plan and exit")
    args = ap.parse_args(argv)

    bundle = load_bundle(args.bundle)
    state_dir = os.path.abspath(args.state_dir)
    subs = {
        "state-dir": state_dir,
        "backend": args.backend,
        "node-name": args.node_name,
        "events-socket": args.events_socket
        or os.path.join(state_dir, "events.sock"),
    }
    # ephemeral ports for every component that declares the
    # metrics/health port pair in the bundle (daemon, daemon-multihost,
    # manager — components with OTHER ports, e.g. metrics-proxy, do not
    # accept these flags)
    extra = (
        {
            name: ["--metrics-port", "0", "--health-port", "0"]
            for name, comp in bundle["components"].items()
            if "metrics" in comp.get("ports", {})
        }
        if args.ephemeral_ports else {}
    )
    flag_env = {
        "coordinator": args.coordinator,
        "num-processes": args.num_processes,
        "process-id": args.process_id,
    }
    if args.with_metrics_proxy and args.component not in (None, "metrics-proxy"):
        # silently dropping the proxy would leave the operator believing
        # off-node metrics are TLS-fronted while nothing is listening
        raise SystemExit(
            "--with-metrics-proxy joins the DEFAULT composition; with "
            f"--component {args.component} nothing would launch the proxy "
            "— run a second launcher with --component metrics-proxy"
        )
    include = ["metrics-proxy"] if (
        args.with_metrics_proxy and args.component is None
        and "metrics-proxy" in bundle["components"]
    ) else []
    proxy_in_plan = args.component == "metrics-proxy" or bool(include)
    if proxy_in_plan:
        # DEFAULT-ON TLS (satellite of the reference posture: the
        # kube-rbac-proxy sidecar always terminates TLS): mint a
        # self-signed pair under the state dir unless the operator
        # explicitly opted into plaintext.  The bearer-token file the
        # run template points at is bootstrapped alongside so a fresh
        # state dir comes up authenticated, never open.
        proxy_args = []
        if not args.insecure_metrics:
            crt = os.path.join(state_dir, "tls", "metrics-tls.crt")
            key = os.path.join(state_dir, "tls", "metrics-tls.key")
            if not args.dry_run:
                if REPO_DIR not in sys.path:  # invoked by absolute path
                    sys.path.insert(0, REPO_DIR)
                from infw.obs.metricsproxy import ensure_self_signed

                crt, key = ensure_self_signed(os.path.join(state_dir, "tls"))
            proxy_args += ["--certfile", crt, "--keyfile", key]
        if not args.dry_run:
            token_path = os.path.join(state_dir, "metrics-token")
            if not os.path.exists(token_path):
                import secrets

                os.makedirs(state_dir, exist_ok=True)
                fd = os.open(token_path + ".tmp",
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "w") as f:
                    f.write(secrets.token_hex(32))
                os.replace(token_path + ".tmp", token_path)
        extra = dict(extra)
        extra["metrics-proxy"] = extra.get("metrics-proxy", []) + proxy_args
    plan = build_plan(bundle, subs, extra, only=args.component,
                      flag_env=flag_env, include=include)
    print(f"launch: bundle {bundle['name']} v{bundle['version']} "
          f"({len(plan)} components)", flush=True)
    if args.dry_run:
        for name, argv_, env in plan:
            print(f"  {name}: {' '.join(shlex.quote(a) for a in argv_)}")
            # envFromFlags routing is part of the plan — print it so a
            # dry run (and the tests) can verify the injected contract
            injected = bundle["components"][name].get("envFromFlags", {})
            for var in injected:
                if var in env:
                    print(f"    env {var}={env[var]}")
        return 0
    return launch(plan, state_dir)


if __name__ == "__main__":
    sys.exit(main())
