#!/usr/bin/env bash
# Single-node composition: the daemonset pod re-expressed as processes —
# daemon (dataplane) + events sidecar (cmd/syslog analogue) + manager
# (fan-out controller), wired exactly like
# /root/reference/bindata/manifests/daemon/daemonset.yaml:25-113 wires its
# three containers (shared state volume -> state dir, syslog unix socket
# -> unixgram events socket, metrics 39301 / health 39300).
#
# The composition itself — component run lines, launch order, env
# contract — is declared in deploy/bundle/manifest.json (the OLM bundle
# role); this script only resolves the deployment knobs and delegates to
# the bundle-driven launcher.
#
# The metrics proxy (the kube-rbac-proxy sidecar role) joins the
# composition by default with TLS ON: launch.py mints a self-signed pair
# under $STATE_DIR/tls (reused across restarts) unless the operator
# provides one.  Plaintext metrics require the EXPLICIT opt-out
# INFW_INSECURE_METRICS=1 (the bearer token then travels in the clear);
# INFW_METRICS_PROXY=0 drops the proxy entirely (loopback-only metrics).
#
# Usage: deploy/compose/single-node.sh [STATE_DIR] [BACKEND]
set -euo pipefail

STATE_DIR="${1:-/var/lib/infw}"
BACKEND="${2:-${INFW_BACKEND:-tpu}}"
NODE_NAME="${NODE_NAME:-$(hostname)}"
EVENTS_SOCK="${INFW_EVENTS_SOCKET:-$STATE_DIR/events.sock}"
REPO_DIR="$(cd "$(dirname "$0")/../.." && pwd)"

# falsy-value parsing matches launch.py exactly (case-insensitive "",
# 0, false, no, off) so the TLS posture cannot invert between entry
# points; tr (not ${var,,}) keeps bash 3.2 working
lower() { printf '%s' "$1" | tr '[:upper:]' '[:lower:]'; }
EXTRA=()
case "$(lower "${INFW_METRICS_PROXY:-1}")" in
  ""|0|false|no|off) ;;
  *) EXTRA+=(--with-metrics-proxy) ;;
esac
case "$(lower "${INFW_INSECURE_METRICS:-}")" in
  ""|0|false|no|off) ;;
  *) EXTRA+=(--insecure-metrics) ;;
esac

# ${EXTRA[@]+...}: expanding an EMPTY array as "${EXTRA[@]}" trips
# `set -u` on bash < 4.4
exec python "$REPO_DIR/deploy/launch.py" \
  --state-dir "$STATE_DIR" \
  --backend "$BACKEND" \
  --node-name "$NODE_NAME" \
  --events-socket "$EVENTS_SOCK" \
  ${EXTRA[@]+"${EXTRA[@]}"}
