#!/usr/bin/env bash
# Single-node composition: the daemonset pod re-expressed as processes —
# daemon (dataplane) + events sidecar (cmd/syslog analogue) + manager
# (fan-out controller), wired exactly like
# /root/reference/bindata/manifests/daemon/daemonset.yaml:25-113 wires its
# three containers (shared state volume -> state dir, syslog unix socket
# -> unixgram events socket, metrics 39301 / health 39300).
#
# The composition itself — component run lines, launch order, env
# contract — is declared in deploy/bundle/manifest.json (the OLM bundle
# role); this script only resolves the deployment knobs and delegates to
# the bundle-driven launcher.
#
# Usage: deploy/compose/single-node.sh [STATE_DIR] [BACKEND]
set -euo pipefail

STATE_DIR="${1:-/var/lib/infw}"
BACKEND="${2:-${INFW_BACKEND:-tpu}}"
NODE_NAME="${NODE_NAME:-$(hostname)}"
EVENTS_SOCK="${INFW_EVENTS_SOCKET:-$STATE_DIR/events.sock}"
REPO_DIR="$(cd "$(dirname "$0")/../.." && pwd)"

exec python "$REPO_DIR/deploy/launch.py" \
  --state-dir "$STATE_DIR" \
  --backend "$BACKEND" \
  --node-name "$NODE_NAME" \
  --events-socket "$EVENTS_SOCK"
