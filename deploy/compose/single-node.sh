#!/usr/bin/env bash
# Single-node composition: the daemonset pod re-expressed as processes —
# daemon (dataplane) + events sidecar (cmd/syslog analogue) + manager
# (fan-out controller), wired exactly like
# /root/reference/bindata/manifests/daemon/daemonset.yaml:25-113 wires its
# three containers (shared state volume -> state dir, syslog unix socket
# -> unixgram events socket, metrics 39301 / health 39300).
#
# Usage: deploy/compose/single-node.sh [STATE_DIR] [BACKEND]
set -euo pipefail

STATE_DIR="${1:-/var/lib/infw}"
BACKEND="${2:-${INFW_BACKEND:-tpu}}"
NODE_NAME="${NODE_NAME:-$(hostname)}"
EVENTS_SOCK="${INFW_EVENTS_SOCKET:-$STATE_DIR/events.sock}"
REPO_DIR="$(cd "$(dirname "$0")/../.." && pwd)"

mkdir -p "$STATE_DIR"
cd "$REPO_DIR"

pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; wait || true; }
trap cleanup EXIT INT TERM

# events sidecar first so the daemon's datagrams have a listener
python -m infw.obs.sidecar --socket "$EVENTS_SOCK" &
pids+=($!)

# manager: fan-out controller + admission + NodeState export; CRs are
# applied by dropping IngressNodeFirewall JSONs into $STATE_DIR/apply
# (admission verdicts land beside them as <name>.status.json)
DAEMONSET_IMAGE="${DAEMONSET_IMAGE:-infw:latest}" \
DAEMONSET_NAMESPACE="${DAEMONSET_NAMESPACE:-ingress-node-firewall-system}" \
python -m infw.manager --export-dir "$STATE_DIR" --apply-dir "$STATE_DIR/apply" \
  --register-node "$NODE_NAME" &
pids+=($!)

# daemon in the foreground (no exec: the EXIT trap must outlive it so a
# daemon crash also tears down the sidecar and manager)
NODE_NAME="$NODE_NAME" python -m infw.daemon \
  --state-dir "$STATE_DIR" \
  --backend "$BACKEND" \
  --events-socket "$EVENTS_SOCK"
