#!/usr/bin/env bash
# Multi-host composition: the DaemonSet-scale-out analogue. One daemon
# process per host joined into a single JAX job over DCN
# (infw/parallel/multihost.py). Run this script on EVERY host with the
# same coordinator address and a unique INFW_PROCESS_ID.
#
#   host0: INFW_PROCESS_ID=0 deploy/compose/multi-host.sh host0:8476 4
#   host1: INFW_PROCESS_ID=1 deploy/compose/multi-host.sh host0:8476 4
#   ...
#
# The per-packet pmax/psum rules-axis combine stays on each host's ICI;
# only the data axis and the final stats reduction cross DCN.
#
# The run line comes from the BUNDLE (deploy/bundle/manifest.json,
# component daemon-multihost) via the launcher — this script only maps
# its positional contract onto launcher flags, the same way
# single-node.sh does.
set -euo pipefail

COORD="${1:?usage: multi-host.sh COORDINATOR_HOST:PORT NUM_PROCESSES [STATE_DIR]}"
NPROC="${2:?usage: multi-host.sh COORDINATOR_HOST:PORT NUM_PROCESSES [STATE_DIR]}"
STATE_DIR="${3:-/var/lib/infw}"
REPO_DIR="$(cd "$(dirname "$0")/../.." && pwd)"

cd "$REPO_DIR"
mkdir -p "$STATE_DIR"

# Metrics exposed off-host go through the TLS proxy: launch it alongside
# (separate launcher process — daemon-multihost is a standalone
# component) with `deploy/launch.py --component metrics-proxy
# --state-dir "$STATE_DIR"`; TLS is on by default (self-signed pair
# minted under $STATE_DIR/tls), plaintext only behind the explicit
# INFW_INSECURE_METRICS=1 opt-out that launch.py honors.

NODE_NAME="${NODE_NAME:-$(hostname)}" \
exec python deploy/launch.py \
  --component daemon-multihost \
  --coordinator "$COORD" \
  --num-processes "$NPROC" \
  --process-id "${INFW_PROCESS_ID:?set INFW_PROCESS_ID to this hosts rank}" \
  --state-dir "$STATE_DIR" \
  --backend "${INFW_BACKEND:-tpu}"
