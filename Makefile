# Build/test entry points (the reference's Makefile equivalent, reduced to
# what is meaningful for the TPU framework: /root/reference/Makefile's
# test / test-race / ebpf-generate / bench roles).

PY ?= python

.PHONY: test test-fast bench bench-checked build-bench slo-bench \
	churn-bench flow-bench resident-bench telemetry-bench mlscore-bench \
	payload-bench pipeline-bench native entry-check dryrun-multichip \
	mesh-check \
	spill-read wire-check lint static-check state-check lock-check \
	sched-check bounds-check clean

# 8 virtual host devices for every CPU-side audit/gate: the mesh serving
# entrypoints (classify-mesh/*) need a multi-device pool to build, and a
# single-device audit would silently skip them.
MESH_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

# Full suite including slow-marked scale tests (1M analyzer tier, full
# registry audit); the tier-1 budgeted run and test-fast exclude them.
test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x -m "not slow"

# One JSON line on stdout; diagnostics on stderr (driver contract).
bench:
	$(PY) bench.py

# Build the native C++ reference classifier explicitly (normally built
# on demand by infw.backend.cpu_ref — the bpf2go-generate analogue).
native:
	$(MAKE) -C infw/backend/native

# Single-chip compile check of the flagship forward step, then the
# static hot-path audit (x64 leaks, host callbacks, recompile lint,
# Pallas VMEM budget) over every registered jitted entrypoint —
# --strict so warnings fail CI too.
entry-check:
	$(PY) -c "import __graft_entry__ as g, jax; fn, args = g.entry(); \
	jax.block_until_ready(jax.jit(fn)(*args)); print('entry OK')"
	$(MESH_ENV) $(PY) tools/infw_lint.py jax --strict

# Lint (ruff when installed, AST fallback otherwise — same conservative
# F + E9 rule set; see pyproject.toml [tool.ruff]).
lint:
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check infw tools tests deploy bench.py __graft_entry__.py; \
	else \
		$(PY) tools/_lint_fallback.py; \
	fi

# Patch-path model checker (infw.analysis.statecheck): seeded op
# sequences over the device-table edit state machine — after every op
# the incrementally-patched device state must be bit-identical to a
# cold rebuild and classify-equivalent to the CPU oracle — plus two
# injected-defect acceptances:
#   1. --inject-defect (joined-pad) re-introduces the PR-4 joined-
#      placeholder bucket-padding bug; the checker must catch it with a
#      shrunk reproducer of <= 3 ops (exit 0 = caught);
#   2. --inject-defect cskip zeroes the compressed layout's skip-node
#      chain-bits words (jaxpath._INJECT_CSKIP_BUG); resident and cold
#      rebuild share the defect, so the catch must come from oracle
#      divergence — proving the classify-equivalence half covers the
#      skip-node path;
#   3. --inject-defect flowstale drops the flow tier's generation-bump
#      invalidation (infw.flow._INJECT_FLOW_STALE_BUG): a rule edit
#      then leaves the exact-match flow cache serving the PRE-edit
#      verdict — device state, host model and cold rebuild all agree,
#      so the catch must be oracle divergence on the flow-path witness,
#      shrunk to a (flow_traffic, rules_edit) pair;
#   4. --inject-defect cowleak makes the CoW arena's clone path forget
#      the donor page's refcount decrement (jaxpath._INJECT_COWLEAK_
#      BUG); check_arena's refcount-vs-page-table-rows invariant must
#      catch it on the shared-then-edited-biased arena-cow config;
#   5. --inject-defect spliceleak makes the subtree-splicing arena's
#      unsplice path forget the old plane's refcount decrement
#      (jaxpath._INJECT_SPLICELEAK_BUG); check_arena's plane-refcount-
#      vs-splice-row-recount invariant must catch it on the near-copy-
#      biased arena-splice config;
#   6. the strict jax audit must FAIL on a deliberately injected
#      implicit host->device transfer (and pass without it — the plain
#      strict audit runs in entry-check/static-check);
#   7. the bounds verifier acceptances: --inject-defect clampgather
#      (drop the spliced page-table & mask decode; caught as oob-gather
#      with a diverging bank-1 witness) and --inject-defect i8wrap
#      (int8 restage of the AC carried DFA state; caught as int-wrap
#      with a diverging deep-state payload witness) — each in a fresh
#      process, the flags act at trace time.
# The full defect inventory is declarative (infw.analysis.defects);
# `infw_lint acceptance` loops it end to end.
# Must be green before any bench record is published (benchruns/README).
state-check:
	$(MESH_ENV) $(PY) tools/infw_lint.py state --strict
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect cskip
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect fold
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect pageflip
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect cowleak
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect spliceleak
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect flowstale
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect residentstale
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect slotepoch
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect sketchsat
	$(MESH_ENV) $(PY) tools/infw_lint.py state --inject-defect mlquant
	$(MESH_ENV) $(PY) tools/infw_lint.py bounds --inject-defect clampgather
	$(MESH_ENV) $(PY) tools/infw_lint.py bounds --inject-defect i8wrap
	@$(MESH_ENV) $(PY) tools/infw_lint.py jax --strict \
		--inject-donation-defect --entries defect/undonated-buffer \
		>/dev/null 2>&1; rc=$$?; \
	if [ $$rc -eq 1 ]; then \
		echo "donation-lint injection caught"; \
	elif [ $$rc -eq 0 ]; then \
		echo "state-check FAIL: injected undonated buffer NOT caught"; \
		exit 1; \
	else \
		echo "state-check FAIL: donation audit exited $$rc (want 1 = caught)"; \
		exit 1; \
	fi
	$(MAKE) sched-check
	@$(MESH_ENV) $(PY) tools/infw_lint.py jax --strict \
		--inject-transfer-defect --entries defect/implicit-transfer \
		>/dev/null 2>&1; rc=$$?; \
	if [ $$rc -eq 1 ]; then \
		echo "transfer-lint injection caught"; \
	elif [ $$rc -eq 0 ]; then \
		echo "state-check FAIL: injected implicit transfer NOT caught"; \
		exit 1; \
	else \
		echo "state-check FAIL: inject audit exited $$rc (want 1 = caught)"; \
		exit 1; \
	fi

# Repo-level static gate: rule-table semantics + jitted hot-path audit
# + the patch-path model checker.
#   1. examples lint — the shipped deny-all example INTENTIONALLY denies
#      failsafe ports (that finding is the analyzer's demo; see README
#      "Static analysis"), so that one check is silenced here;
#   2. the acceptance gate: a table with one injected shadowed rule and
#      one Allow/Deny conflict must report EXACTLY those two findings,
#      each witness confirmed by replay against the CPU oracle;
#   3. the jax audit across the shape ladder, strict (incl. the
#      transfer-guard lint);
#   4. the state checker with its injected-defect acceptances.
# Concurrency verifier (ISSUE-18): the static lock-order/guard pass
# (repo-wide, zero unsuppressed findings) plus its lockorder
# injected-defect acceptance, and the deterministic interleaving
# explorer's four production scenarios plus the cowrace acceptance.
lock-check:
	$(PY) tools/infw_lint.py lock --strict
	$(PY) tools/infw_lint.py lock --inject-defect lockorder

sched-check:
	$(MESH_ENV) $(PY) tools/infw_lint.py sched --strict
	$(MESH_ENV) $(PY) tools/infw_lint.py sched --inject-defect cowrace

# Kernel admission verifier (infw.analysis.boundscheck): jaxpr abstract
# interpretation over EVERY registered entrypoint, seeded from the
# declared tensor bounds (infw.contracts.TENSOR_BOUNDS — the same
# declarations statecheck's runtime invariant sweeps enforce), proving
# gather/scatter/dynamic_slice bounds and integer-overflow freedom.
# Intentional modular arithmetic lives in
# infw/analysis/boundscheck_suppressions.txt with required
# justifications; --strict means zero unsuppressed findings.  The two
# injected-defect acceptances (clampgather, i8wrap) run in state-check
# (fresh processes — the flags act at trace time).
bounds-check:
	$(MESH_ENV) $(PY) tools/infw_lint.py bounds --strict

static-check: lint
	$(PY) tools/infw_lint.py rules --ignore failsafe-violation --strict
	$(PY) tools/infw_lint.py rules --acceptance
	$(MESH_ENV) $(PY) tools/infw_lint.py jax --strict
	$(MAKE) bounds-check
	$(MAKE) lock-check
	$(MAKE) state-check
	@echo "static-check OK"

# The 1M cold-build microbenchmark (bench.bench_build): vectorized
# columnar compiler vs the retired per-key reference on the SAME host
# and content, runs INTERLEAVED so both see the same ambient load,
# output bit-identity checked, with a regression threshold on the
# measured speedup (INFW_BUILD_SPEEDUP_MIN, default 1.3x — observed
# 1.7-2.3x interleaved on the 2-core CI host, up to ~5x under memory
# pressure, while a reversion to per-key work lands at ~1x; the
# recorded-baseline ratio is in the emitted vs_baseline field).
build-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --build-bench

# The SLO serving tier (bench.bench_slo) standalone at a smoke load
# off-TPU: open-loop Poisson arrivals through the deadline-aware
# continuous microbatching scheduler (infw.scheduler), p50/p99/p999
# above link floor at 3 offered loads, deadline-miss rate, achieved
# batch sizes, and the fixed-ingest_chunk A/B — gated on the scheduled
# path's p99-above-floor beating the baseline (INFW_SLO_P99_MAX_RATIO,
# default 0.9x; verdicts are oracle-checked inside the tier).
slo-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --slo-bench

# The update-storm churn tier (bench.bench_churn) standalone at a smoke
# load off-TPU: folded 64-edit transaction vs the sequential
# one-edit-one-generation path (amortized per-edit A/B, gated on
# INFW_CHURN_SPEEDUP_MIN, default 5x), plus sustained edits/s under a
# fixed offered classify load with p99 edit-visible latency and a
# classify-throughput retention gate (INFW_CHURN_RETENTION_MIN, default
# 0.9).  The statecheck multi-op transaction equivalence (txn configs)
# runs inside the gate BEFORE any record is published.
churn-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --churn-bench

# The multi-tenant arena tier (bench.bench_tenant) standalone at smoke
# scale off-TPU: pre-staged tenant hot-swap (page-table row flip) vs
# the full re-upload A/B (interleaved min-vs-min, gated on
# INFW_SWAP_SPEEDUP_MIN, default 10x — the ISSUE-10 acceptance), plus
# mixed-tenant batch vs sequential per-tenant dispatch at 64 tenants
# and the arena-vs-N-padded-tables HBM footprint line.  Mixed-batch
# verdicts are oracle-checked bit-exact inside the tier, and the
# statecheck arena equivalence configs run BEFORE any record is
# published.
tenant-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --tenant-bench

# The structural-compression ladder (bench.bench_splice) standalone at
# smoke scale off-TPU: a drift chain of similar-NOT-identical tenants
# (every tenant a k-edit delta of its predecessor, k in {1, 16, 256})
# through the shared-subtree splice layer — HBM bytes/tenant vs one
# flat slab per tenant (gated on INFW_SPLICE_BYTES_RATIO_MIN, default
# 10x at the k=16 rung over 2.5K CPU tenants, the ISSUE-17
# acceptance), the splice-indirect walk-latency tax vs a flat arena
# (INFW_SPLICE_WALK_TAX_MAX, default 2x, interleaved min-vs-min), and
# the zero-recompile warm drift lifecycle.  Sampled tenants are
# oracle-checked bit-exact inside the tier, and the arena-splice
# statecheck config runs BEFORE any record is published.
# INFW_SPLICE_TENANTS overrides the gate rung's tenant count.
splice-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --splice-bench

# The stateful flow tier (bench.bench_flow) standalone at smoke scale
# off-TPU: classify throughput at the 0/50/90/99% established-flow
# ladder (flow tier vs the stateless baseline, interleaved, verdicts
# oracle-gated bit-exact per rung), the eviction-storm line (flow table
# much smaller than the flow population), and the zero-recompile warm
# flow lifecycle — gated on the 90%-point speedup
# (INFW_FLOW_SPEEDUP_MIN, default 1.15x).  The statecheck flow configs
# run inside the gate BEFORE any record is published.
flow-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --flow-bench

# The zero-copy resident serving tier (bench.bench_resident) standalone
# at smoke scale off-TPU: per-admission p50 latency of the ONE-fused-
# program donated-buffer loop vs the probe-then-classify multi-dispatch
# plan at batch 32/128 (interleaved min-vs-min, same trace, both flow
# tiers reset per pass), gated on the batch-32 speedup
# (INFW_RESIDENT_SPEEDUP_MIN, default 3x — the ISSUE-12 acceptance),
# with verdict bit-identity to the CPU oracle AND the multi-dispatch
# path gated in-tier, plus a warmed 1000-dispatch steady-state run that
# asserts ZERO resident-pool allocations and ZERO recompiles.  The
# statecheck resident config runs FIRST and gates record publication.
resident-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --resident-bench

# The device-resident telemetry tier (bench.bench_telemetry) standalone
# at smoke scale off-TPU: served classify-throughput retention with the
# in-kernel sketches on vs off at a FIXED OFFERED LOAD (70% of the
# sketches-off capacity, calibrated in-record — telemetry must fit the
# serving headroom; gated at INFW_TELEMETRY_RETENTION_MIN, default
# 0.95, with the raw full-speed dispatch A/B reported ungated beside
# it), a warmed zero-recompile/zero-alloc steady-state run with
# sketches on (the resident-bench discipline), attack-detection
# latency on synflood/denystorm traces (drained summaries must surface
# the planted attacker), and a live in-process --telemetry --trace
# daemon leg whose /metrics must serve the per-stage span histograms
# and whose event log the per-tenant heavy-hitter summaries.  Verdicts
# and sketch tensors are oracle-gated bit-exact in-tier, and the
# statecheck telemetry config runs FIRST and gates record publication.
telemetry-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --telemetry-bench

# The MXU anomaly-scoring tier (bench.bench_mlscore) standalone at
# smoke scale off-TPU: shadow-mode device scores bit-identical to the
# HostScoreModel oracle AND verdicts bit-identical to the scoring-off
# path + the CPU oracle (gated before any timing line), detection
# precision >= INFW_MLSCORE_PRECISION_MIN (default 0.95) and recall >=
# INFW_MLSCORE_RECALL_MIN (default 0.9) on the seeded synflood +
# portscan traces with detection latency reported beside them, served
# classify-throughput retention with scoring on at a FIXED OFFERED
# LOAD (70% of the scoring-off capacity, gated at
# INFW_MLSCORE_RETENTION_MIN, default 0.95), a warmed zero-recompile /
# zero-alloc steady state with scoring on, and an enforce-mode leg
# (attacker flows denied, failsafe cells never rewritten).  The
# statecheck mlscore config runs FIRST and gates record publication.
mlscore-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --mlscore-bench

# The payload matching tier (bench.bench_payload) standalone at smoke
# scale off-TPU: device/host bit-identity of the Aho-Corasick match
# bitmaps vs the naive substring oracle across the classic + resident
# fused paths BEFORE any timing line, the standalone automaton ladder
# (64/256/1024 patterns x 64/128 prefix bytes), served classify
# retention with matching on at a FIXED OFFERED LOAD (70% of the
# headers-only capacity, gated at INFW_PAYLOAD_RETENTION_MIN, default
# 0.9, at the 64-pattern/64-byte rung), a warmed zero-recompile /
# zero-alloc run spanning an in-bucket hot swap + mode flips, and an
# enforce-mode leg (signature lanes denied, failsafe cells never
# rewritten).  The statecheck payload configs run FIRST and gate
# record publication.
payload-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --payload-bench

# The pipelined-admission tier (bench.bench_pipeline) standalone at
# smoke scale off-TPU: the K=4 device-side superbatch epoch loop + the
# two-slot overlap vs the single-dispatch resident loop, packets/s
# above the link floor at batch 32 and 128, interleaved min-vs-min,
# gated at INFW_PIPELINE_OVERLAP_MIN (default 1.3x, the ISSUE-16
# acceptance).  Superbatch verdicts/stats/flow-columns/sketch tensors
# are gated bit-identical to K sequential fused dispatches in-tier, a
# warmed steady-state run cycling BOTH pipeline slots asserts zero
# allocations + zero recompiles, and the DeviceStripe mesh leg reports
# packets/s + device-busy fraction at 1/2/4/8 devices (hence the
# 8-virtual-device MESH_ENV).  The statecheck pipeline config runs
# FIRST and gates record publication.
pipeline-bench:
	$(MESH_ENV) $(PY) bench.py --pipeline-bench

# Bench behind the static gate (benchruns/README.md: jaxpr drift must
# not silently change what the bench measures).  `make bench` itself is
# left untouched — its stdout is a driver contract.
bench-checked: static-check build-bench slo-bench churn-bench tenant-bench splice-bench flow-bench resident-bench telemetry-bench mlscore-bench payload-bench pipeline-bench bench

# Wire-codec gate: the delta+varint codec unit/fuzz suite plus a
# 10K-packet replay smoke through the real daemon ingest on CPU
# (verdicts checked bit-exact vs the oracle, delta engagement asserted).
wire-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_wire_codec.py -q
	JAX_PLATFORMS=cpu $(PY) tools/wire_smoke.py

# Decode a binary deny-event spill into reference-format event lines
# (the operator-facing consumer of the sustained-rate event path).
# Usage: make spill-read SPILL=path/to/deny-events.bin [ARGS=--follow]
spill-read:
	$(PY) tools/spill_read.py $(SPILL) $(ARGS)

# Full distributed step on a virtual 8-device CPU mesh, then the
# measured multi-chip throughput ladder (bench.multichip_ladder) whose
# final MULTICHIP_BENCH line is the driver's MULTICHIP record.
dryrun-multichip:
	$(MESH_ENV) \
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# Multi-chip serving gate: the mesh parity suite (MeshTpuClassifier vs
# single-chip TpuClassifier vs the CPU oracle, incl. reshard/overlay/
# edge cases) plus the smoke scaling bench — all on 8 simulated host
# devices, so the production mesh path is exercised on every run
# without TPU hardware.
mesh-check:
	$(MESH_ENV) $(PY) -m pytest tests/test_mesh.py tests/test_mesh_serving.py -q
	$(MAKE) dryrun-multichip

clean:
	rm -rf infw/backend/native/_build **/__pycache__ .pytest_cache
