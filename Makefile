# Build/test entry points (the reference's Makefile equivalent, reduced to
# what is meaningful for the TPU framework: /root/reference/Makefile's
# test / test-race / ebpf-generate / bench roles).

PY ?= python

.PHONY: test test-fast bench native entry-check dryrun-multichip \
	spill-read wire-check clean

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x

# One JSON line on stdout; diagnostics on stderr (driver contract).
bench:
	$(PY) bench.py

# Build the native C++ reference classifier explicitly (normally built
# on demand by infw.backend.cpu_ref — the bpf2go-generate analogue).
native:
	$(MAKE) -C infw/backend/native

# Single-chip compile check of the flagship forward step.
entry-check:
	$(PY) -c "import __graft_entry__ as g, jax; fn, args = g.entry(); \
	jax.block_until_ready(jax.jit(fn)(*args)); print('entry OK')"

# Wire-codec gate: the delta+varint codec unit/fuzz suite plus a
# 10K-packet replay smoke through the real daemon ingest on CPU
# (verdicts checked bit-exact vs the oracle, delta engagement asserted).
wire-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_wire_codec.py -q
	JAX_PLATFORMS=cpu $(PY) tools/wire_smoke.py

# Decode a binary deny-event spill into reference-format event lines
# (the operator-facing consumer of the sustained-rate event path).
# Usage: make spill-read SPILL=path/to/deny-events.bin [ARGS=--follow]
spill-read:
	$(PY) tools/spill_read.py $(SPILL) $(ARGS)

# Full distributed step on a virtual 8-device CPU mesh.
dryrun-multichip:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	rm -rf infw/backend/native/_build **/__pycache__ .pytest_cache
